"""Cross-host forensics receipt (the tentpole acceptance): a real
2-process gloo run where rank 1 deliberately skips one collective must
leave per-host flight-recorder dumps whose tpu_doctor merge names the
diverging rank and the last mismatched (axis, op, seq) — the exact
point the pod's programs stopped agreeing. Also covers the
obs_report --doctor bridge over the same dumps."""
import glob
import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def divergence_dumps(tmp_path_factory):
    """One 2-process run shared by the assertions below."""
    out = tmp_path_factory.mktemp("fr")
    env = dict(os.environ)
    env.update({
        "PD_TEST_RDZV_PORT": str(_free_port()),
        "PD_TEST_COORD_PORT": str(_free_port()),
        "PD_FR_DIR": str(out),
        # children pick their own backend; scrub the test-session forcing
        "XLA_FLAGS": "",
    })
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", "2",
           os.path.join(REPO, "tests", "doctor_divergence_worker.py")]
    res = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                         text=True, timeout=150)
    assert res.returncode == 0, (
        f"launch failed\nstdout:\n{res.stdout}\nstderr:\n{res.stderr}")
    paths = sorted(glob.glob(str(out / "flight_*.json")))
    assert len(paths) == 2, f"expected 2 rank dumps, got {paths}"
    return out, paths


def test_doctor_names_skipping_rank(divergence_dumps):
    from tools.tpu_doctor import diagnose, load_dumps
    _, paths = divergence_dumps
    dumps = load_dumps(paths)
    assert [d["rank"] for d in dumps] == [0, 1]
    div = diagnose(dumps)["divergence"]
    assert div is not None, "divergence not detected"
    assert div["diverging_rank"] == 1
    assert div["diverging_ranks"] == [1]
    assert div["op"] == "allreduce_sum"
    # rank 1 made 2 calls, rank 0 made 3: seq 2 is the first call not
    # executed everywhere — the last mismatched collective
    assert div["mismatched_seq"] == 2
    # the matched prologue stays clean: barrier counts agree
    ops = {m["op"] for m in div["detail"]}
    assert "barrier" not in ops


def test_doctor_cli_verdict(divergence_dumps, capsys):
    from tools import tpu_doctor
    out, _ = divergence_dumps
    rc = tpu_doctor.main(["--dir", str(out)])
    text = capsys.readouterr().out
    assert rc == 1                         # triage verdict: wrong pod
    assert "DIVERGENCE" in text and "rank 1" in text
    assert "allreduce_sum" in text and "seq=2" in text


def test_doctor_cli_json_and_obs_report_bridge(divergence_dumps,
                                               capsys):
    from tools import obs_report, tpu_doctor
    out, _ = divergence_dumps
    rc = tpu_doctor.main(["--dir", str(out), "--json"])
    diag = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert diag["divergence"]["diverging_rank"] == 1
    # one operator surface: obs_report --doctor hands off to tpu_doctor
    rc2 = obs_report.main(["--doctor", str(out), "--doctor-json"])
    diag2 = json.loads(capsys.readouterr().out)
    assert rc2 == 1 and diag2["divergence"] == diag["divergence"]
