"""Tier-1 comm-bench smoke: guards the ISSUE-5 acceptance receipts
against regression —
  - bucketing keeps the fused collective count at <= 1/4 of the
    per-tensor count at ERNIE-tiny scale (the >=4x reduction),
  - bf16 wire bytes stay <= 0.55x the f32 baseline,
  - the f32 default remains bit-for-bit against the pre-PR sync,
  - the flight recorder sees enter/exit per FUSED collective.

Runs tools/comm_bench.py (single-process leg; the 2-process gloo leg
stays out of tier-1 — tests/test_comm_hier_dist.py covers cross-process
collectives) in a subprocess, mirroring test_pipeline_bench_smoke.py.
Budget: <15 s (ROADMAP tier-1 rebalance policy)."""
import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

_ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}
# the parent test process pins an 8-device virtual mesh; the bench
# subprocess picks its own backend
_ENV.pop("XLA_FLAGS", None)
_ENV.pop("PD_COMM_BENCH_DIST", None)


@pytest.mark.slow  # 16.2 s on the slowed sandbox; test_comm.py's
#   18 planner/bucket/wire-tier tests keep the comm contracts in
#   tier-1; the bench acceptance ratios re-prove via -m slow
def test_comm_bench_receipts(tmp_path):
    jsonl = str(tmp_path / "comm_bench.jsonl")
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "comm_bench.py")],
        capture_output=True, text=True, timeout=240,
        env={**_ENV, "PD_OBS_JSONL": jsonl}, cwd=ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    stats = json.loads(p.stdout.strip().splitlines()[-1])

    # the printed report and the JSONL series come from ONE code path
    rec = json.loads(open(jsonl).read().splitlines()[-1])
    exported = {k[len("bench.comm."):]: v["value"] if isinstance(
        v, dict) and "value" in v else v
        for k, v in rec["metrics"].items()
        if k.startswith("bench.comm.")}
    assert exported == stats, (
        "JSONL export diverged from the printed bench report")

    # fused-bucket count: >= 4x fewer collectives than per-tensor
    assert stats["per_tensor_collectives"] == stats["n_grad_tensors"]
    assert stats["fused_collectives"] >= 1
    assert stats["collective_count_ratio"] <= 0.25, stats

    # wire-bytes receipts: the counters ARE the accounting
    assert stats["wire_bytes_f32"] == stats["per_tensor_wire_bytes"]
    assert stats["wire_ratio_bf16"] <= 0.55, stats
    assert stats["wire_ratio_int8_ef"] <= 0.30, stats

    # exactness + flight-recorder convention (per fused collective,
    # not per tensor)
    assert stats["f32_bit_exact"] is True
    assert stats["fr_enter_events"] == stats["fused_collectives"]
