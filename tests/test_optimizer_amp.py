"""Optimizer, LR scheduler, and AMP tests (reference test_adam_op.py /
test_imperative_optimizer.py / test_amp_* style)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.optimizer import (SGD, Adam, AdamW, Adagrad, Momentum,
                                  RMSProp, Lamb)
from paddle_tpu.optimizer import lr as lr_sched


def _train_quadratic(opt_cls, steps=120, **kw):
    paddle.seed(7)
    w = paddle.Parameter(np.array([5.0, -3.0], np.float32))
    opt = opt_cls(parameters=[w], **kw)
    for _ in range(steps):
        loss = ((w - paddle.to_tensor([1.0, 2.0])) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w.numpy(), opt


def test_sgd_converges():
    w, _ = _train_quadratic(SGD, learning_rate=0.1)
    np.testing.assert_allclose(w, [1.0, 2.0], atol=1e-3)


def test_momentum_converges():
    w, _ = _train_quadratic(Momentum, learning_rate=0.05, momentum=0.9)
    np.testing.assert_allclose(w, [1.0, 2.0], atol=1e-2)


def test_adam_converges_and_matches_reference_step():
    w, opt = _train_quadratic(Adam, learning_rate=0.1, steps=300)
    np.testing.assert_allclose(w, [1.0, 2.0], atol=1e-2)
    # single-step numeric check against hand formula
    p = paddle.Parameter(np.array([1.0], np.float32))
    opt = Adam(learning_rate=0.1, parameters=[p])
    (p * 3.0).sum().backward()
    opt.step()
    # m=0.1*3(>beta1 part)... m=(1-.9)*3=0.3, v=(1-.999)*9=0.009
    m_hat = 0.3 / (1 - 0.9)
    v_hat = 0.009 / (1 - 0.999)
    expected = 1.0 - 0.1 * m_hat / (np.sqrt(v_hat) + 1e-8)
    np.testing.assert_allclose(p.numpy(), [expected], rtol=1e-5)


def test_adamw_decoupled_decay():
    p = paddle.Parameter(np.array([1.0], np.float32))
    opt = AdamW(learning_rate=0.1, parameters=[p], weight_decay=0.5)
    (p * 0.0).sum().backward()  # zero grad → update only from decay
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.5 * 1.0],
                               rtol=1e-5)


def test_rmsprop_adagrad_lamb_run():
    for cls, kw in [(RMSProp, {"learning_rate": 0.05}),
                    (Adagrad, {"learning_rate": 0.5}),
                    (Lamb, {"learning_rate": 0.05})]:
        w, _ = _train_quadratic(cls, steps=200, **kw)
        np.testing.assert_allclose(w, [1.0, 2.0], atol=0.3)


def test_optimizer_state_dict_roundtrip():
    w, opt = _train_quadratic(Adam, learning_rate=0.1, steps=5)
    sd = opt.state_dict()
    p2 = paddle.Parameter(np.array([5.0, -3.0], np.float32))
    opt2 = Adam(learning_rate=0.1, parameters=[p2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == opt._step_count


def test_grad_clip_in_optimizer():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=1.0, parameters=[w],
              grad_clip=ClipGradByGlobalNorm(0.1))
    (w * 100.0).sum().backward()
    opt.step()
    # grad clipped to 0.1 → w = 1 - 0.1
    np.testing.assert_allclose(w.numpy(), [0.9], rtol=1e-5)


def test_lr_schedulers():
    s = lr_sched.StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    cos = lr_sched.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(cos.lr_at(0) - 1.0) < 1e-6
    assert abs(cos.lr_at(10)) < 1e-6

    warm = lr_sched.LinearWarmup(0.5, warmup_steps=10, start_lr=0.0,
                                 end_lr=0.5)
    assert warm.lr_at(5) == pytest.approx(0.25)
    assert warm.lr_at(20) == pytest.approx(0.5)

    noam = lr_sched.NoamDecay(d_model=512, warmup_steps=100)
    assert noam.lr_at(50) < noam.lr_at(100)

    plateau = lr_sched.ReduceOnPlateau(0.1, patience=1)
    for loss in [1.0, 1.0, 1.0, 1.0]:
        plateau.step(loss)
    assert plateau() < 0.1


def test_scheduler_drives_optimizer():
    sched = lr_sched.StepDecay(0.5, step_size=1, gamma=0.1)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=sched, parameters=[w])
    (w * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [0.5], rtol=1e-6)  # lr=0.5
    sched.step()
    opt.clear_grad()
    (w * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [0.45], rtol=1e-5)  # lr=0.05


def test_auto_cast_white_list():
    import jax.numpy as jnp
    with paddle.amp.auto_cast(level="O1"):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        c = paddle.matmul(a, b)
        assert c.dtype == jnp.bfloat16
        # black-list op stays fp32
        s = F.softmax(c)
        assert s.dtype == jnp.float32
    # outside context: fp32 matmul
    c2 = paddle.matmul(a, b)
    assert c2.dtype == jnp.float32


def test_auto_cast_grads_flow():
    w = paddle.Parameter(np.ones((4, 4), np.float32))
    x = paddle.randn([2, 4])
    with paddle.amp.auto_cast():
        y = paddle.matmul(x, w)
        loss = y.astype("float32").sum()
    loss.backward()
    assert w.grad is not None
    assert str(w.grad.dtype) == "float32"  # grad cast back to param dtype


def test_grad_scaler():
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=0.1, parameters=[w])
    loss = (w * 2.0).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    # unscaled grad = 2 → w = 1 - 0.2
    np.testing.assert_allclose(w.numpy(), [0.8], rtol=1e-5)
    assert scaler.get_loss_scaling() == 1024.0


def test_grad_scaler_skips_on_inf():
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=1)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=0.1, parameters=[w])
    w._grad = np.array([np.inf], np.float32)
    import jax.numpy as jnp
    w._grad = jnp.asarray([jnp.inf], jnp.float32)
    scaler.step(opt)
    np.testing.assert_allclose(w.numpy(), [1.0])  # step skipped
    assert scaler.get_loss_scaling() == 512.0  # scale halved


# -- in-graph AMP: master weights + compiled loss scaling --------------------
# (reference operators/amp/check_finite_and_unscale_op.cc,
#  update_loss_scaling_op.cc, python/paddle/optimizer/adam.py multi_precision)

class TestMasterWeights:
    def test_fp16_adam_keeps_fp32_master(self):
        import jax.numpy as jnp
        p = paddle.create_parameter([4], "float16")
        p._data = jnp.ones(4, jnp.float16)
        opt = paddle.optimizer.Adam(learning_rate=1e-4, parameters=[p],
                                    multi_precision=True)
        # 100 updates of ~1e-4: pure-fp16 accumulation would stall
        # (1.0 + 1e-4 rounds back to 1.0 in fp16); master fp32 must not
        for _ in range(100):
            p._grad = jnp.ones(4, jnp.float16)
            opt.step()
        st = opt._accumulators[id(p)]
        assert st["master_weight"].dtype == jnp.float32
        assert st["moment1"].dtype == jnp.float32
        # param tracks cast-down master; master itself moved ~100*1e-4
        assert float(st["master_weight"][0]) < 1.0 - 5e-3
        assert p._data.dtype == jnp.float16
        np.testing.assert_allclose(
            np.asarray(p._data), np.asarray(
                st["master_weight"].astype(jnp.float16)))

    def test_fp16_without_master_stalls(self):
        # control: the failure mode master weights exist to fix
        import jax.numpy as jnp
        p = paddle.create_parameter([4], "float16")
        p._data = jnp.ones(4, jnp.float16)
        opt = paddle.optimizer.SGD(learning_rate=1e-4, parameters=[p])
        for _ in range(10):
            p._grad = jnp.ones(4, jnp.float16)
            opt.step()
        np.testing.assert_array_equal(np.asarray(p._data),
                                      np.ones(4, np.float16))

    def test_momentum_multi_precision_tree_api(self):
        import jax.numpy as jnp
        opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                        multi_precision=True)
        params = {"w": jnp.ones(3, jnp.float16)}
        st = opt.init_state_tree(params)
        assert st["w"]["master_weight"].dtype == jnp.float32
        grads = {"w": jnp.full(3, 0.5, jnp.float16)}
        new_p, new_st = opt.apply_gradients_tree(params, grads, st)
        assert new_p["w"].dtype == jnp.float16
        np.testing.assert_allclose(
            np.asarray(new_st["w"]["master_weight"]),
            1.0 - 0.1 * 0.5, rtol=1e-6)


class TestInGraphLossScaling:
    def _make_step(self, scaler, amp_level="O2", amp_dtype="float16"):
        from paddle_tpu.static.train_step import TrainStep
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    multi_precision=True)
        return TrainStep(net, lambda o, y: F.mse_loss(o, y), opt,
                         amp_level=amp_level, amp_dtype=amp_dtype,
                         scaler=scaler)

    def test_o2_fp16_trains_and_scale_state_in_graph(self):
        import jax.numpy as jnp
        from paddle_tpu.amp import GradScaler
        scaler = GradScaler(init_loss_scaling=2.0 ** 8,
                            incr_every_n_steps=4)
        step = self._make_step(scaler)
        # params were cast down; optimizer holds fp32 masters
        assert all(v.dtype == jnp.float16 for v in step.params.values())
        assert all(st["master_weight"].dtype == jnp.float32
                   for st in step.opt_state.values())
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype(np.float32)
        y = rng.randn(16, 4).astype(np.float32)
        losses = [float(step(paddle.to_tensor(x),
                             paddle.to_tensor(y)).item())
                  for _ in range(12)]
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()
        # clean steps: scale grew (incr_every_n=4, 12 clean steps)
        assert float(step.strategy_state["amp_scale"]) > 2.0 ** 8

    def test_overflow_skips_update_and_decays_scale(self):
        import jax.numpy as jnp
        from paddle_tpu.amp import GradScaler
        scaler = GradScaler(init_loss_scaling=2.0 ** 10)
        step = self._make_step(scaler)
        rng = np.random.RandomState(1)
        x = rng.randn(8, 8).astype(np.float32)
        y = rng.randn(8, 4).astype(np.float32)
        step(paddle.to_tensor(x), paddle.to_tensor(y))  # warmup/compile
        before = {k: np.asarray(v) for k, v in step.params.items()}
        scale_before = float(step.strategy_state["amp_scale"])
        bad = x.copy()
        bad[0, 0] = np.inf
        loss = step(paddle.to_tensor(bad), paddle.to_tensor(y))
        # skipped-step semantics: params and opt state unchanged
        for k, v in step.params.items():
            np.testing.assert_array_equal(before[k], np.asarray(v))
        assert float(step.strategy_state["amp_scale"]) == \
            scale_before * 0.5
        # recovery: clean step still trains afterwards
        l2 = step(paddle.to_tensor(x), paddle.to_tensor(y))
        assert np.isfinite(float(l2.item()))

    def test_amp_ops_under_jit(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.amp.functional import (
            check_finite_and_unscale_tree, update_loss_scaling_state)

        @jax.jit
        def f(tree, scale):
            g, inf = check_finite_and_unscale_tree(tree, scale)
            s, good, bad = update_loss_scaling_state(
                scale, jnp.asarray(3, jnp.int32),
                jnp.asarray(0, jnp.int32), inf)
            return g, inf, s
        tree = {"a": jnp.ones(3) * 8.0, "b": jnp.ones(2)}
        g, inf, s = f(tree, jnp.asarray(4.0, jnp.float32))
        assert not bool(inf)
        np.testing.assert_allclose(np.asarray(g["a"]), 2.0)
        tree["b"] = jnp.array([1.0, np.nan])
        g, inf, s = f(tree, jnp.asarray(4.0, jnp.float32))
        assert bool(inf) and float(s) == 2.0


@pytest.mark.slow  # >15 s on the tier-1 sandbox; run via -m slow
def test_ernie_tiny_fp16_o2_trains():
    """fp16 O2 end-to-end (VERDICT item 5 done-criterion): ERNIE-tiny
    under TrainStep with in-graph dynamic loss scaling + master weights
    trains; an injected overflow batch is skipped without corrupting
    state."""
    import jax.numpy as jnp
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining
    from paddle_tpu.static.train_step import TrainStep
    from paddle_tpu.amp import GradScaler
    paddle.seed(42)
    cfg = ErnieConfig.tiny()
    model = ErnieForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 multi_precision=True)
    scaler = GradScaler(init_loss_scaling=2.0 ** 10)
    step = TrainStep(
        model,
        lambda out, y: ErnieForPretraining.pretraining_loss(out, y),
        opt, amp_level="O2", amp_dtype="float16", scaler=scaler)
    assert any(v.dtype == jnp.float16 for v in step.params.values())
    rng = np.random.RandomState(3)
    ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    losses = [float(step(paddle.to_tensor(ids),
                         paddle.to_tensor(labels)).item())
              for _ in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # memorizes the fixed batch
