"""Optimizer, LR scheduler, and AMP tests (reference test_adam_op.py /
test_imperative_optimizer.py / test_amp_* style)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.optimizer import (SGD, Adam, AdamW, Adagrad, Momentum,
                                  RMSProp, Lamb)
from paddle_tpu.optimizer import lr as lr_sched


def _train_quadratic(opt_cls, steps=120, **kw):
    paddle.seed(7)
    w = paddle.Parameter(np.array([5.0, -3.0], np.float32))
    opt = opt_cls(parameters=[w], **kw)
    for _ in range(steps):
        loss = ((w - paddle.to_tensor([1.0, 2.0])) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w.numpy(), opt


def test_sgd_converges():
    w, _ = _train_quadratic(SGD, learning_rate=0.1)
    np.testing.assert_allclose(w, [1.0, 2.0], atol=1e-3)


def test_momentum_converges():
    w, _ = _train_quadratic(Momentum, learning_rate=0.05, momentum=0.9)
    np.testing.assert_allclose(w, [1.0, 2.0], atol=1e-2)


def test_adam_converges_and_matches_reference_step():
    w, opt = _train_quadratic(Adam, learning_rate=0.1, steps=300)
    np.testing.assert_allclose(w, [1.0, 2.0], atol=1e-2)
    # single-step numeric check against hand formula
    p = paddle.Parameter(np.array([1.0], np.float32))
    opt = Adam(learning_rate=0.1, parameters=[p])
    (p * 3.0).sum().backward()
    opt.step()
    # m=0.1*3(>beta1 part)... m=(1-.9)*3=0.3, v=(1-.999)*9=0.009
    m_hat = 0.3 / (1 - 0.9)
    v_hat = 0.009 / (1 - 0.999)
    expected = 1.0 - 0.1 * m_hat / (np.sqrt(v_hat) + 1e-8)
    np.testing.assert_allclose(p.numpy(), [expected], rtol=1e-5)


def test_adamw_decoupled_decay():
    p = paddle.Parameter(np.array([1.0], np.float32))
    opt = AdamW(learning_rate=0.1, parameters=[p], weight_decay=0.5)
    (p * 0.0).sum().backward()  # zero grad → update only from decay
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 0.5 * 1.0],
                               rtol=1e-5)


def test_rmsprop_adagrad_lamb_run():
    for cls, kw in [(RMSProp, {"learning_rate": 0.05}),
                    (Adagrad, {"learning_rate": 0.5}),
                    (Lamb, {"learning_rate": 0.05})]:
        w, _ = _train_quadratic(cls, steps=200, **kw)
        np.testing.assert_allclose(w, [1.0, 2.0], atol=0.3)


def test_optimizer_state_dict_roundtrip():
    w, opt = _train_quadratic(Adam, learning_rate=0.1, steps=5)
    sd = opt.state_dict()
    p2 = paddle.Parameter(np.array([5.0, -3.0], np.float32))
    opt2 = Adam(learning_rate=0.1, parameters=[p2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == opt._step_count


def test_grad_clip_in_optimizer():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=1.0, parameters=[w],
              grad_clip=ClipGradByGlobalNorm(0.1))
    (w * 100.0).sum().backward()
    opt.step()
    # grad clipped to 0.1 → w = 1 - 0.1
    np.testing.assert_allclose(w.numpy(), [0.9], rtol=1e-5)


def test_lr_schedulers():
    s = lr_sched.StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    cos = lr_sched.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(cos.lr_at(0) - 1.0) < 1e-6
    assert abs(cos.lr_at(10)) < 1e-6

    warm = lr_sched.LinearWarmup(0.5, warmup_steps=10, start_lr=0.0,
                                 end_lr=0.5)
    assert warm.lr_at(5) == pytest.approx(0.25)
    assert warm.lr_at(20) == pytest.approx(0.5)

    noam = lr_sched.NoamDecay(d_model=512, warmup_steps=100)
    assert noam.lr_at(50) < noam.lr_at(100)

    plateau = lr_sched.ReduceOnPlateau(0.1, patience=1)
    for loss in [1.0, 1.0, 1.0, 1.0]:
        plateau.step(loss)
    assert plateau() < 0.1


def test_scheduler_drives_optimizer():
    sched = lr_sched.StepDecay(0.5, step_size=1, gamma=0.1)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=sched, parameters=[w])
    (w * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [0.5], rtol=1e-6)  # lr=0.5
    sched.step()
    opt.clear_grad()
    (w * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [0.45], rtol=1e-5)  # lr=0.05


def test_auto_cast_white_list():
    import jax.numpy as jnp
    with paddle.amp.auto_cast(level="O1"):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        c = paddle.matmul(a, b)
        assert c.dtype == jnp.bfloat16
        # black-list op stays fp32
        s = F.softmax(c)
        assert s.dtype == jnp.float32
    # outside context: fp32 matmul
    c2 = paddle.matmul(a, b)
    assert c2.dtype == jnp.float32


def test_auto_cast_grads_flow():
    w = paddle.Parameter(np.ones((4, 4), np.float32))
    x = paddle.randn([2, 4])
    with paddle.amp.auto_cast():
        y = paddle.matmul(x, w)
        loss = y.astype("float32").sum()
    loss.backward()
    assert w.grad is not None
    assert str(w.grad.dtype) == "float32"  # grad cast back to param dtype


def test_grad_scaler():
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=0.1, parameters=[w])
    loss = (w * 2.0).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    # unscaled grad = 2 → w = 1 - 0.2
    np.testing.assert_allclose(w.numpy(), [0.8], rtol=1e-5)
    assert scaler.get_loss_scaling() == 1024.0


def test_grad_scaler_skips_on_inf():
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=1)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=0.1, parameters=[w])
    w._grad = np.array([np.inf], np.float32)
    import jax.numpy as jnp
    w._grad = jnp.asarray([jnp.inf], jnp.float32)
    scaler.step(opt)
    np.testing.assert_allclose(w.numpy(), [1.0])  # step skipped
    assert scaler.get_loss_scaling() == 512.0  # scale halved
