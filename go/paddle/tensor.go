package paddle

// Tensor is the host-side value passed to / received from a Predictor
// (the reference tensor.go holds shape + data; dtype here is the C API
// dtype string: "float32", "int32", "int64", "bool").
type Tensor struct {
	Name  string
	Shape []int64
	Dtype string
	// exactly one of these is set, matching Dtype
	FloatData []float32
	Int32Data []int32
	Int64Data []int64
	BoolData  []bool
}

// NewFloatTensor builds a float32 input tensor.
func NewFloatTensor(name string, shape []int64, data []float32) *Tensor {
	return &Tensor{Name: name, Shape: shape, Dtype: "float32",
		FloatData: data}
}

// NewInt64Tensor builds an int64 input tensor (ids, labels).
func NewInt64Tensor(name string, shape []int64, data []int64) *Tensor {
	return &Tensor{Name: name, Shape: shape, Dtype: "int64",
		Int64Data: data}
}

func (t *Tensor) numel() int64 {
	n := int64(1)
	for _, s := range t.Shape {
		n *= s
	}
	return n
}
