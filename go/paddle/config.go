// Package paddle is the Go inference/training client over the
// paddle_tpu C API (csrc/paddle_tpu_capi.h), mirroring the reference
// go/paddle/{config,predictor,tensor}.go surface.
//
// Build: the cgo directives below expect the shared library built by
// `make -C csrc libpaddletpu_capi.so`; set CGO_LDFLAGS/LD_LIBRARY_PATH
// to the csrc directory. NOTE: this build image ships no Go toolchain,
// so this client is compile-verified only against the C header — run
// `go vet ./...` + the demo on a machine with Go installed.
package paddle

/*
#cgo CFLAGS: -I${SRCDIR}/../../csrc
#cgo LDFLAGS: -L${SRCDIR}/../../csrc -lpaddletpu_capi
#include <stdlib.h>
#include "paddle_tpu_capi.h"
*/
import "C"
import (
	"errors"
	"unsafe"
)

// Init starts the embedded runtime; call once, before anything else.
func Init(repoRoot string) error {
	c := C.CString(repoRoot)
	defer C.free(unsafe.Pointer(c))
	if C.PD_Init(c) != 0 {
		return lastError()
	}
	return nil
}

// Finalize tears the runtime down.
func Finalize() { C.PD_Finalize() }

func lastError() error {
	msg := C.GoString(C.PD_GetLastError())
	if msg == "" {
		msg = "unknown paddle_tpu C API error"
	}
	return errors.New(msg)
}

// AnalysisConfig mirrors the reference's config.go over
// PD_AnalysisConfig.
type AnalysisConfig struct {
	c *C.PD_AnalysisConfig
}

func NewAnalysisConfig() *AnalysisConfig {
	return &AnalysisConfig{c: C.PD_NewAnalysisConfig()}
}

// SetModel points the config at a saved inference model
// (static.save_inference_model prefix + params path).
func (cfg *AnalysisConfig) SetModel(modelPrefix, paramsPath string) {
	m := C.CString(modelPrefix)
	p := C.CString(paramsPath)
	defer C.free(unsafe.Pointer(m))
	defer C.free(unsafe.Pointer(p))
	C.PD_SetModel(cfg.c, m, p)
}

func (cfg *AnalysisConfig) Delete() {
	if cfg.c != nil {
		C.PD_DeleteAnalysisConfig(cfg.c)
		cfg.c = nil
	}
}
