package paddle

/*
#include <stdlib.h>
#include "paddle_tpu_capi.h"
*/
import "C"
import (
	"fmt"
	"unsafe"
)

// Predictor mirrors the reference predictor.go over PD_Predictor
// (csrc/capi.cpp AnalysisPredictor path: jax.export-compiled program).
type Predictor struct {
	c *C.PD_Predictor
}

func NewPredictor(cfg *AnalysisConfig) (*Predictor, error) {
	p := C.PD_NewPredictor(cfg.c)
	if p == nil {
		return nil, lastError()
	}
	return &Predictor{c: p}, nil
}

func (p *Predictor) Delete() {
	if p.c != nil {
		C.PD_DeletePredictor(p.c)
		p.c = nil
	}
}

func (p *Predictor) GetInputNum() int  { return int(C.PD_GetInputNum(p.c)) }
func (p *Predictor) GetOutputNum() int { return int(C.PD_GetOutputNum(p.c)) }

func (p *Predictor) GetInputName(i int) string {
	return C.GoString(C.PD_GetInputName(p.c, C.int(i)))
}

// SetInput feeds one named input tensor.
func (p *Predictor) SetInput(t *Tensor) error {
	var data unsafe.Pointer
	switch t.Dtype {
	case "float32":
		data = unsafe.Pointer(&t.FloatData[0])
	case "int32":
		data = unsafe.Pointer(&t.Int32Data[0])
	case "int64":
		data = unsafe.Pointer(&t.Int64Data[0])
	default:
		return fmt.Errorf("unsupported input dtype %q", t.Dtype)
	}
	name := C.CString(t.Name)
	dtype := C.CString(t.Dtype)
	defer C.free(unsafe.Pointer(name))
	defer C.free(unsafe.Pointer(dtype))
	rc := C.PD_PredictorSetInput(
		p.c, name, data, dtype,
		(*C.int64_t)(unsafe.Pointer(&t.Shape[0])),
		C.int(len(t.Shape)))
	if rc != 0 {
		return lastError()
	}
	return nil
}

// Run executes the compiled program on the feeds set so far.
func (p *Predictor) Run() error {
	if C.PD_PredictorRun(p.c) != 0 {
		return lastError()
	}
	return nil
}

// GetOutput copies output i (converted to float32 by the C API).
func (p *Predictor) GetOutput(i int) (*Tensor, error) {
	ndim := int(C.PD_GetOutputNdim(p.c, C.int(i)))
	if ndim < 0 {
		return nil, lastError()
	}
	shape := make([]int64, ndim)
	if ndim > 0 {
		if C.PD_GetOutputShape(p.c, C.int(i),
			(*C.int64_t)(unsafe.Pointer(&shape[0]))) != 0 {
			return nil, lastError()
		}
	}
	n := int64(1)
	for _, s := range shape {
		n *= s
	}
	out := make([]float32, n)
	var dst *C.float
	if n > 0 {
		dst = (*C.float)(unsafe.Pointer(&out[0]))
	}
	got := int64(C.PD_CopyOutputFloat(p.c, C.int(i), dst, C.int64_t(n)))
	if got < 0 {
		return nil, lastError()
	}
	return &Tensor{Shape: shape, Dtype: "float32",
		FloatData: out[:got]}, nil
}

// TrainSession wraps PD_TrainSession (the C++ train-demo capability:
// load a serialized Program, run optimizer steps, save params back).
type TrainSession struct {
	c *C.PD_TrainSession
}

func NewTrainSession(programPath, lossName, optimizer string,
	lr float32) (*TrainSession, error) {
	pp := C.CString(programPath)
	ln := C.CString(lossName)
	op := C.CString(optimizer)
	defer C.free(unsafe.Pointer(pp))
	defer C.free(unsafe.Pointer(ln))
	defer C.free(unsafe.Pointer(op))
	s := C.PD_NewTrainSession(pp, ln, op, C.float(lr))
	if s == nil {
		return nil, lastError()
	}
	return &TrainSession{c: s}, nil
}

func (s *TrainSession) Delete() {
	if s.c != nil {
		C.PD_DeleteTrainSession(s.c)
		s.c = nil
	}
}

func (s *TrainSession) SetFeed(t *Tensor) error {
	var data unsafe.Pointer
	switch t.Dtype {
	case "float32":
		data = unsafe.Pointer(&t.FloatData[0])
	case "int64":
		data = unsafe.Pointer(&t.Int64Data[0])
	case "int32":
		data = unsafe.Pointer(&t.Int32Data[0])
	default:
		return fmt.Errorf("unsupported feed dtype %q", t.Dtype)
	}
	name := C.CString(t.Name)
	dtype := C.CString(t.Dtype)
	defer C.free(unsafe.Pointer(name))
	defer C.free(unsafe.Pointer(dtype))
	rc := C.PD_TrainSessionSetFeed(
		s.c, name, data, dtype,
		(*C.int64_t)(unsafe.Pointer(&t.Shape[0])),
		C.int(len(t.Shape)))
	if rc != 0 {
		return lastError()
	}
	return nil
}

// RunStep runs one fused train step and returns the loss.
func (s *TrainSession) RunStep() (float32, error) {
	var loss C.float
	if C.PD_TrainSessionRunStep(s.c, &loss) != 0 {
		return 0, lastError()
	}
	return float32(loss), nil
}

// Save writes trained parameters back into the program file at path.
func (s *TrainSession) Save(path string) error {
	p := C.CString(path)
	defer C.free(unsafe.Pointer(p))
	if C.PD_TrainSessionSave(s.c, p) != 0 {
		return lastError()
	}
	return nil
}
