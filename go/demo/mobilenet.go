// Demo mirroring the reference go/demo/mobilenet.go: load a saved
// inference model and run one batch.
//
//	go run mobilenet.go -model /path/to/prefix -params /path/to/prefix.pdiparams
package main

import (
	"flag"
	"fmt"
	"log"

	paddle "paddle_tpu/go/paddle"
)

func main() {
	model := flag.String("model", "model", "inference model prefix")
	params := flag.String("params", "", "params path (defaults beside prefix)")
	repo := flag.String("repo", "../..", "paddle_tpu repo root")
	flag.Parse()

	if err := paddle.Init(*repo); err != nil {
		log.Fatal(err)
	}
	defer paddle.Finalize()

	cfg := paddle.NewAnalysisConfig()
	defer cfg.Delete()
	cfg.SetModel(*model, *params)

	pred, err := paddle.NewPredictor(cfg)
	if err != nil {
		log.Fatal(err)
	}
	defer pred.Delete()

	batch := []float32{}
	for i := 0; i < 1*3*224*224; i++ {
		batch = append(batch, 0.5)
	}
	in := paddle.NewFloatTensor(pred.GetInputName(0),
		[]int64{1, 3, 224, 224}, batch)
	if err := pred.SetInput(in); err != nil {
		log.Fatal(err)
	}
	if err := pred.Run(); err != nil {
		log.Fatal(err)
	}
	out, err := pred.GetOutput(0)
	if err != nil {
		log.Fatal(err)
	}
	fmt.Printf("output shape %v, first vals %v\n",
		out.Shape, out.FloatData[:4])
}
